"""Cold-start microbench: warm-pool handoff vs fresh spawn, snap A/B.

Measures the north-star metric (cold-start-to-first-step) through the REAL
stack — scheduler placement → worker → interpreter → first output — with
server-stamped timestamps (TaskGetTimeline), in three configurations:

1. fresh spawn (warm pool off): exec container_entrypoint per placement
2. warm-pool handoff: placement adopted by a pre-forked parked interpreter
3. snapshot A/B on the warm-pool path: fresh @enter(snap=True) vs
   warm-state restore (runtime/snapshot.py) — both without process re-exec

Prints ONE line: COLDSTART_BENCH_RESULT {json}. bench.py folds the fields
into the round result as coldstart_*. The warm_pool_hit field is the
acceptance proof that the measured path went through a parked interpreter.

Run directly: JAX_PLATFORMS=cpu python tools/bench_coldstart.py
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def _make_app(tag: str):
    import modal_tpu

    app = modal_tpu.App(f"coldstart-bench-{tag}")

    @app.function(serialized=True, timeout=120)
    def first_step(x: int) -> int:
        # representative first step: import jax (free on the warm path — the
        # parked interpreter pre-imported it) and run one jitted computation
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(v):
            return (v * 2.0 + 1.0).sum()

        return float(f(jnp.ones((256, 256)) * x).block_until_ready())

    return app, first_step


def _make_snap_app():
    import modal_tpu

    app = modal_tpu.App("coldstart-bench-snap")

    @app.cls(serialized=True, enable_memory_snapshot=True, timeout=120)
    class SnapModel:
        @modal_tpu.enter(snap=True)
        def load(self):
            import jax
            import jax.numpy as jnp

            # the expensive enter: init + one jit (what restore skips)
            key = jax.random.PRNGKey(0)
            self.w = jax.random.normal(key, (512, 512))
            self.b = jnp.ones((512,))
            (self.w @ self.b).block_until_ready()

        @modal_tpu.method()
        def step(self) -> float:
            import jax.numpy as jnp

            return float(jnp.tanh(self.w @ self.b).sum())

    return app, SnapModel


def _timed_call(app, fn, *args) -> tuple[float, bool]:
    """(server-stamped cold_start_to_first_step_s, warm_pool_hit)."""
    with app.run():
        fc = fn.spawn(*args)
        fc.get(timeout=120)
        tl = fc.get_timeline()
    t0 = tl.tasks[0]
    return t0.first_output_at - t0.created_at, t0.warm_pool_hit


def _timed_snap_call(app, snap_model) -> tuple[float, bool]:
    with app.run():
        obj = snap_model()
        fc = obj.step.spawn()
        fc.get(timeout=120)
        tl = fc.get_timeline()
    t0 = tl.tasks[0]
    return t0.first_output_at - t0.created_at, t0.warm_pool_hit


def _boot_supervisor(warm_pool: int):
    from modal_tpu._utils.async_utils import synchronizer
    from modal_tpu.client import _Client
    from modal_tpu.server.supervisor import LocalSupervisor

    state_dir = tempfile.mkdtemp(prefix="coldstart_bench_")
    os.environ["MODAL_TPU_STATE_DIR"] = state_dir
    os.environ["MODAL_TPU_WARM_POOL"] = str(warm_pool)
    sup = LocalSupervisor(
        num_workers=1, state_dir=state_dir, worker_chips=8, worker_tpu_type="local-sim"
    )
    synchronizer.run(sup.start())
    os.environ["MODAL_TPU_SERVER_URL"] = sup.server_url
    _Client.set_env_client(None)
    return sup, synchronizer


def main() -> None:
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("MODAL_TPU_JAX_PLATFORM", "cpu")
    os.environ["MODAL_TPU_AUTO_LOCAL_SERVER"] = "0"
    result: dict = {}

    # --- 1. fresh-spawn baseline (pool off) --------------------------------
    sup, synchronizer = _boot_supervisor(warm_pool=0)
    app, first_step = _make_app("fresh")
    cold_fresh, hit = _timed_call(app, first_step, 3)
    assert not hit, "pool-off run must not report a warm hit"
    result["cold_start_fresh_spawn_s"] = round(cold_fresh, 3)
    synchronizer.run(sup.stop())

    # --- 2. warm-pool handoff ----------------------------------------------
    sup, synchronizer = _boot_supervisor(warm_pool=1)
    pool = sup.workers[0].pool
    assert synchronizer.run(pool.wait_parked(1, 120.0)), "warm pool never parked"
    app, first_step = _make_app("warm")
    cold_warm, hit = _timed_call(app, first_step, 3)
    result["cold_start_warm_pool_s"] = round(cold_warm, 3)
    result["warm_pool_hit"] = bool(hit)
    if cold_warm > 0:
        result["warm_pool_speedup"] = round(cold_fresh / cold_warm, 2)

    # --- 3. snapshot A/B on the warm path ----------------------------------
    synchronizer.run(pool.wait_parked(1, 60.0))
    snap_app, snap_model = _make_snap_app()
    fresh_enter, hit_a = _timed_snap_call(snap_app, snap_model)
    synchronizer.run(pool.wait_parked(1, 60.0))
    restore, hit_b = _timed_snap_call(snap_app, snap_model)
    result["cold_start_fresh_enter_s"] = round(fresh_enter, 3)
    result["cold_start_snap_restore_s"] = round(restore, 3)
    result["snap_warm_pool_hit"] = bool(hit_a and hit_b)
    if restore > 0:
        result["snap_restore_speedup"] = round(fresh_enter / restore, 2)
    from modal_tpu.observability.catalog import WARM_POOL_PLACEMENTS

    result["warm_pool_hits_total"] = int(WARM_POOL_PLACEMENTS.value(outcome="hit"))
    synchronizer.run(sup.stop())

    print("COLDSTART_BENCH_RESULT " + json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
