"""Fleet compile-cache microbench (ISSUE 20): cold-fleet rollout + donation A/B.

Two arms, printed as ONE line ``COMPILE_BENCH_RESULT {json}`` for bench.py
to fold in as ``compile_*`` (BENCH_compile.json guard):

1. **Cold-fleet rollout**: container A (fresh fleet store) compiles the AOT
   ``sample`` entry point plus a small jit program suite and publishes;
   container B — a different process with a different local persistent-cache
   dir, the exact condition that used to poison jax's cache keys — runs the
   identical programs against the primed store. Acceptance:
   ``primed_misses == 0`` and ``primed_puts == 0`` (zero in-container XLA
   compiles), plus the wall-clock speedup that buys.
2. **Donation A/B**: the tiny train step jitted with ``donate_argnums=(0,)``
   vs byte-identical body without donation — steady-state step time for
   both (the donated step updates params+opt state in place; the undonated
   one allocates a second copy of the carried state every step).

Run directly: JAX_PLATFORMS=cpu python tools/bench_compile.py
"""

from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys
import tempfile
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

_ROLLOUT_DRIVER = """
import json, sys, time
import jax, jax.numpy as jnp
jax.config.update("jax_compilation_cache_dir", sys.argv[1])
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
from modal_tpu.runtime.compile_client import install_fleet_cache
assert install_fleet_cache(), "fleet tier must install"

t0 = time.monotonic()
# a realistic model compile: the serving sample step against abstract shapes
from modal_tpu.runtime.aot import run_aot_lowering
results = run_aot_lowering(["sample"], {"cfg": "tiny"})
assert "errors" not in results, results

# plus a small plain-jit suite (distinct shapes -> distinct cache entries)
@jax.jit
def affine(x, w, b):
    return jnp.tanh(x @ w + b).sum()

for n in (16, 32, 64):
    affine(jnp.ones((n, n)), jnp.ones((n, n)), jnp.ones((n,))).block_until_ready()
wall = time.monotonic() - t0

from modal_tpu.observability.catalog import (
    COMPILE_CACHE_HITS, COMPILE_CACHE_MISSES, COMPILE_CACHE_PUTS,
)
def _total(c):
    return c.value(source="local_dir") + c.value(source="http")
print("ROLLOUT " + json.dumps({
    "wall_s": round(wall, 3),
    "hits": _total(COMPILE_CACHE_HITS),
    "misses": _total(COMPILE_CACHE_MISSES),
    "puts": _total(COMPILE_CACHE_PUTS),
}))
"""


def _run_container(fleet_dir: str, local_dir: str, timeout_s: float) -> dict:
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        MODAL_TPU_COMPILE_CACHE="1",
        MODAL_TPU_COMPILE_CACHE_DIR=fleet_dir,
    )
    env.pop("MODAL_TPU_COMPILE_CACHE_URL", None)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-c", _ROLLOUT_DRIVER, local_dir],
        capture_output=True,
        text=True,
        timeout=timeout_s,
        env=env,
        cwd=REPO_ROOT,
    )
    if proc.returncode != 0:
        raise RuntimeError(f"rollout container failed: {proc.stderr[-2000:]}")
    for line in reversed(proc.stdout.splitlines()):
        if line.startswith("ROLLOUT "):
            return json.loads(line[len("ROLLOUT ") :])
    raise RuntimeError("rollout container printed no result")


def bench_cold_rollout(timeout_s: float = 180.0) -> dict:
    with tempfile.TemporaryDirectory(prefix="bench-compile-") as td:
        fleet = os.path.join(td, "fleet")
        os.makedirs(fleet)
        local_a = os.path.join(td, "local-a")
        local_b = os.path.join(td, "local-b")
        os.makedirs(local_a)
        os.makedirs(local_b)
        first = _run_container(fleet, local_a, timeout_s / 2)
        primed = _run_container(fleet, local_b, timeout_s / 2)
    return {
        "first_run_s": first["wall_s"],
        "primed_run_s": primed["wall_s"],
        "primed_speedup_x": round(first["wall_s"] / max(primed["wall_s"], 1e-9), 2),
        "first_misses": first["misses"],
        "first_puts": first["puts"],
        "primed_hits": primed["hits"],
        "primed_misses": primed["misses"],
        "primed_puts": primed["puts"],
    }


def bench_donation_ab(steps: int = 8) -> dict:
    """Steady-state tiny train step: donated (the shipped configuration)
    vs the identical body without donation. CPU numbers understate the HBM
    win (the real payoff is peak memory on TPU), but the in-place loop must
    never be SLOWER, and the delta is the regression canary."""
    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    import jax

    from modal_tpu.models.llama import get_config
    from modal_tpu.parallel.mesh import build_mesh
    from modal_tpu.parallel.train import TrainConfig, create_sharded_state

    cfg = get_config("tiny")
    tc = TrainConfig(warmup_steps=10, total_steps=100)
    mesh = build_mesh({"fsdp": 2, "model": 2})

    def _time_steps(step_fn, state, tokens) -> tuple:
        state, metrics = step_fn(state, tokens)  # warmup: trace + compile
        jax.block_until_ready(metrics)
        times = []
        for _ in range(steps):
            t0 = time.perf_counter()
            state, metrics = step_fn(state, tokens)
            jax.block_until_ready(metrics)
            times.append(time.perf_counter() - t0)
        return statistics.median(times), state

    import jax.numpy as jnp

    with mesh:
        state, donated_step, token_sharding = create_sharded_state(mesh, cfg, tc)
        tokens = jax.device_put(
            jax.random.randint(
                jax.random.PRNGKey(1), (4, 64), 0, cfg.vocab_size, jnp.int32
            ),
            token_sharding,
        )
        donated_s, _ = _time_steps(donated_step, state, tokens)

        # the undonated control: same body, no donation, no out_shardings pin
        # (the pre-audit world)
        from functools import partial

        import optax

        from modal_tpu.parallel.train import TrainState, loss_fn, make_optimizer

        optimizer = make_optimizer(tc)

        @jax.jit
        def undonated_step(state, tokens):
            loss, grads = jax.value_and_grad(
                lambda p, t: loss_fn(p, cfg, t, tc.remat)
            )(state.params, tokens)
            updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
            new_params = optax.apply_updates(state.params, updates)
            return TrainState(new_params, new_opt, state.step + 1), {"loss": loss}

        state2, _, _ = create_sharded_state(mesh, cfg, tc)
        undonated_s, _ = _time_steps(undonated_step, state2, tokens)

    return {
        "donated_step_ms": round(donated_s * 1000, 3),
        "undonated_step_ms": round(undonated_s * 1000, 3),
        "donation_speedup_x": round(undonated_s / max(donated_s, 1e-9), 3),
    }


def main() -> None:
    result: dict = {}
    rollout = bench_cold_rollout()
    result.update(rollout)
    result.update(bench_donation_ab())
    result["zero_compile_rollout"] = bool(
        rollout["primed_misses"] == 0 and rollout["primed_puts"] == 0
    )
    print("COMPILE_BENCH_RESULT " + json.dumps(result))


if __name__ == "__main__":
    main()
