"""No-op dispatch microbench: per-segment latency attribution + profiler A/B.

The ROADMAP item 3 baseline artifact: `measure_call_wall_s` ≈ 0.2 s per
trivial call caps serving throughput, and this bench says WHERE that floor
lives before anyone tries to shave it. It drives N no-op `.remote()` calls
through the REAL stack (supervisor → scheduler → worker → container), then:

1. reads the span store back and computes the critical-path attribution of
   every measured call (observability/critical_path.py) — queue_wait, place,
   handoff, serialize, rpc, user.execute, output delivery, and the honest
   ``gap`` (unaccounted wall time; acceptance: ≤ 10%);
2. re-runs the measured loop with the sampling profiler ON
   (observability/profiler.py) and reports the overhead (acceptance: ≤ 5%).

Prints ONE line: DISPATCH_BENCH_RESULT {json}; bench.py folds the fields in
as ``dispatch_*`` (``dispatch_p50_s``, ``dispatch_attribution``, ...). The
follow-up latency PR must beat these numbers, not vibes.

Run directly: JAX_PLATFORMS=cpu python tools/bench_dispatch.py [--calls 30]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def _make_app(tag: str):
    import modal_tpu

    app = modal_tpu.App(f"dispatch-bench-{tag}")

    @app.function(serialized=True, timeout=120)
    def noop(x: int) -> int:
        return x

    return app, noop


def _boot_supervisor(state_dir: str):
    from modal_tpu._utils.async_utils import synchronizer
    from modal_tpu.client import _Client
    from modal_tpu.server.supervisor import LocalSupervisor

    os.environ["MODAL_TPU_STATE_DIR"] = state_dir
    sup = LocalSupervisor(
        num_workers=1, state_dir=state_dir, worker_chips=8, worker_tpu_type="local-sim"
    )
    synchronizer.run(sup.start())
    os.environ["MODAL_TPU_SERVER_URL"] = sup.server_url
    _Client.set_env_client(None)
    return sup, synchronizer


def _timed_calls(fn, n: int) -> list[float]:
    walls = []
    for i in range(n):
        t0 = time.perf_counter()
        assert fn.remote(i) == i
        walls.append(time.perf_counter() - t0)
    return walls


def _quantile(vals: list[float], q: float) -> float:
    # one quantile contract for the whole report: the bench's p50/p95 must
    # agree with the attribution table computed from the same run
    from modal_tpu.observability.quantile import quantile as shared_quantile

    return shared_quantile(sorted(vals), q)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--calls", type=int, default=30, help="measured no-op calls")
    parser.add_argument("--warmup", type=int, default=3, help="unmeasured warmup calls")
    args = parser.parse_args()

    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("MODAL_TPU_JAX_PLATFORM", "cpu")
    os.environ["MODAL_TPU_AUTO_LOCAL_SERVER"] = "0"
    state_dir = tempfile.mkdtemp(prefix="dispatch_bench_")

    from modal_tpu.observability import critical_path as cp
    from modal_tpu.observability.catalog import DISPATCH_LATENCY

    sup, synchronizer = _boot_supervisor(state_dir)
    result: dict = {}
    try:
        app, noop = _make_app("attr")
        with app.run():
            _timed_calls(noop, args.warmup)  # container boot + jit amortized out
            t_measured0 = time.time()
            walls = _timed_calls(noop, args.calls)
            t_measured1 = time.time()

        result["calls"] = args.calls
        result["p50_s"] = round(_quantile(walls, 0.5), 4)
        result["p95_s"] = round(_quantile(walls, 0.95), 4)
        result["calls_per_s"] = round(args.calls / sum(walls), 2)

        # attribution over the measured window's traces (skip warmup: its
        # cold boot would smear container.boot over the steady-state story)
        trace_dir = os.path.join(state_dir, "traces")
        from modal_tpu.observability import tracing

        traces = {}
        for rec in tracing.read_spans(trace_dir):
            traces.setdefault(rec["trace_id"], []).append(rec)
        measured = [
            spans
            for spans in traces.values()
            if any(
                s["name"] == cp.ROOT_SPAN and t_measured0 <= s["start"] <= t_measured1
                for s in spans
            )
        ]
        per_trace = [a for spans in measured if (a := cp.attribute_trace(spans)) is not None]
        agg = cp.aggregate_attributions(per_trace)
        print(cp.format_attribution_table(agg), file=sys.stderr)
        result["attribution"] = {
            seg: round(v["p50_s"], 5) for seg, v in agg.get("segments", {}).items()
        }
        result["attribution_share"] = {
            seg: round(v["share"], 4) for seg, v in agg.get("segments", {}).items()
        }
        result["gap_share"] = round(agg.get("gap_share", 1.0), 4)
        result["attributed_share"] = round(1.0 - agg.get("gap_share", 1.0), 4)

        # exemplar proof: the dispatch histogram carries trace ids that exist
        # in the store (the acceptance path GET /metrics renders)
        ex_trace_ids = set()
        for series in DISPATCH_LATENCY._series.values():
            ex_trace_ids |= {tid for tid, _v, _t in series.exemplars.values()}
        result["exemplar_trace_ids_resolve"] = bool(ex_trace_ids) and all(
            tid in traces for tid in ex_trace_ids
        )

        # --- profiler overhead A/B on the same loop ------------------------
        # interleaved blocks (off, on, off, on, ...): supervisor state drifts
        # over a run, so back-to-back halves would measure drift, not the
        # sampler; per-call medians of the pooled blocks are drift-robust
        from modal_tpu.observability import profiler

        profiles_dir = os.path.join(state_dir, "observability", "profiles")
        app2, noop2 = _make_app("prof")
        base: list[float] = []
        profiled: list[float] = []
        block = max(3, args.calls // 4)
        with app2.run():
            _timed_calls(noop2, args.warmup)
            for i in range(8):
                if i % 2:
                    profiler.start(profiles_dir, tag="bench", hz=profiler.DEFAULT_HZ)
                    profiled += _timed_calls(noop2, block)
                    profiler.stop()
                else:
                    base += _timed_calls(noop2, block)
        base_p50, prof_p50 = _quantile(base, 0.5), _quantile(profiled, 0.5)
        result["profiler_hz"] = profiler.DEFAULT_HZ
        result["profiler_overhead_pct"] = round(100.0 * (prof_p50 - base_p50) / base_p50, 2)
        result["profiler_samples"] = profiler.current().n_samples if profiler.current() else 0

        # --- concurrency sweep (ISSUE 8 satellite) -------------------------
        # 1/8/64 in-flight callers against one concurrent container: the
        # coalesced submit/claim/publish planes should hold calls/s roughly
        # flat per RPC while concurrency grows
        from concurrent.futures import ThreadPoolExecutor

        import modal_tpu

        app4 = modal_tpu.App("dispatch-bench-sweep")

        def noop_c(x: int) -> int:
            return x

        noop_c = modal_tpu.concurrent(max_inputs=64)(noop_c)
        noop_c = app4.function(serialized=True, timeout=120)(noop_c)
        sweep: dict = {}
        with app4.run():
            _timed_calls(noop_c, args.warmup)
            for conc in (1, 8, 64):
                n_calls = max(16, conc * 3)

                def _one(i: int) -> float:
                    t0 = time.perf_counter()
                    assert noop_c.remote(i) == i
                    return time.perf_counter() - t0

                t_sw0 = time.perf_counter()
                with ThreadPoolExecutor(max_workers=conc) as pool:
                    call_walls = list(pool.map(_one, range(n_calls)))
                wall = time.perf_counter() - t_sw0
                sweep[str(conc)] = {
                    "calls": n_calls,
                    "calls_per_s": round(n_calls / wall, 2),
                    "p50_s": round(_quantile(sorted(call_walls), 0.5), 4),
                    "p95_s": round(_quantile(sorted(call_walls), 0.95), 4),
                }
                print(f"sweep conc={conc}: {sweep[str(conc)]}", file=sys.stderr)
        result["sweep"] = sweep
        result["max_calls_per_s"] = max(v["calls_per_s"] for v in sweep.values())
    finally:
        synchronizer.run(sup.stop())

    print("DISPATCH_BENCH_RESULT " + json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
