#!/usr/bin/env python
"""Data-plane microbench: serialize / blob round-trip / Volume→device GB/s.

Runs against an in-process LocalSupervisor (no workers) so the numbers
measure the data plane itself — out-of-band serialization, streaming blob
HTTP, and the striped Volume read engine — not scheduling. Emits ONE JSON
line (``DATAPLANE_RESULT {...}``) so CI and the bench driver can fold it.

Usage:
    JAX_PLATFORMS=cpu python tools/bench_dataplane.py [--size-mb 1024]

The Volume section reports both the sequential chunk-loop baseline (the
pre-zero-copy ``read_file_into``) and the parallel striped engine; the
acceptance bar is parallel ≥ 2× sequential on a ≥ 1 GiB checkpoint.
"""

from __future__ import annotations

import argparse
import io
import json
import os
import resource
import sys
import tempfile
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def bench_serialization(size_mb: int) -> dict:
    import numpy as np

    from modal_tpu.serialization import deserialize, serialize_payload

    rng = np.random.default_rng(7)
    # a realistic checkpoint-shaped pytree: a few large tensors + metadata
    n = size_mb * 1024 * 1024 // 4 // 4
    tree = {
        "wq": rng.standard_normal(n, dtype=np.float32),
        "wk": rng.standard_normal(n, dtype=np.float32),
        "scales": rng.standard_normal(n, dtype=np.float32),
        "tokens": rng.integers(0, 127, size=n, dtype=np.int32),
        "meta": {"step": 1234, "names": ["wq", "wk"]},
    }
    nbytes = sum(a.nbytes for a in tree.values() if hasattr(a, "nbytes"))
    t0 = time.perf_counter()
    payload = serialize_payload(tree)
    blob = payload.join()
    ser_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    out = deserialize(blob)
    deser_s = time.perf_counter() - t0
    assert out["meta"]["step"] == 1234
    return {
        "serialize_gbps": round(nbytes / ser_s / 1e9, 3),
        "deserialize_gbps": round(nbytes / deser_s / 1e9, 3),
        "payload_overhead_bytes": payload.nbytes - nbytes,
    }


async def _bench_blob(size_mb: int) -> dict:
    import numpy as np

    from modal_tpu._utils.blob_utils import blob_download, blob_upload
    from modal_tpu.client import _Client

    client = await _Client.from_env()
    rng = np.random.default_rng(11)
    payload = rng.integers(0, 256, size=size_mb * 1024 * 1024, dtype=np.uint8).tobytes()
    t0 = time.perf_counter()
    blob_id = await blob_upload(payload, client.stub)
    up_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    back = await blob_download(blob_id, client.stub)
    down_s = time.perf_counter() - t0
    assert bytes(back[:64]) == payload[:64] and len(back) == len(payload)
    spilled = isinstance(back, memoryview)
    return {
        "blob_upload_gbps": round(len(payload) / up_s / 1e9, 3),
        "blob_download_gbps": round(len(payload) / down_s / 1e9, 3),
        "blob_download_spilled": spilled,
    }


async def _bench_volume(size_mb: int) -> dict:
    """Sequential chunk-loop baseline vs the striped parallel engine, plus
    the read_file_range_into→device path the weights loader takes."""
    import numpy as np

    from modal_tpu.client import _Client
    from modal_tpu.volume import _Volume

    client = await _Client.from_env()
    vol = await _Volume.ephemeral(client=client)
    rng = np.random.default_rng(13)
    data = rng.integers(0, 256, size=size_mb * 1024 * 1024, dtype=np.uint8).tobytes()
    async with vol.batch_upload(force=True) as batch:
        batch.put_data(data, "ckpt/blob.bin")

    # sequential baseline: one VolumeBlockGet at a time, appended in order —
    # the single-streamed read the striped engine replaces. Best of 2 runs
    # (both paths) so scheduler noise doesn't skew the ratio.
    from modal_tpu._utils.grpc_utils import retry_transient_errors
    from modal_tpu.proto import api_pb2

    meta = await vol._get_file_meta("ckpt/blob.bin")

    async def _seq_run() -> float:
        t0 = time.perf_counter()
        seq_total = 0
        buf = io.BytesIO()
        for sha in meta.file.block_sha256_hex:
            r = await retry_transient_errors(
                client.stub.VolumeBlockGet, api_pb2.VolumeBlockGetRequest(sha256_hex=sha)
            )
            buf.write(r.data)
            seq_total += len(r.data)
        assert seq_total == len(data)
        return time.perf_counter() - t0

    seq_s = min([await _seq_run() for _ in range(2)])

    # parallel striped engine into a preallocated temp file
    async def _par_run() -> float:
        with tempfile.NamedTemporaryFile(delete=False) as tmp:
            tmp_path = tmp.name
        try:
            with open(tmp_path, "r+b") as f:
                t0 = time.perf_counter()
                got = await vol.read_file_into("ckpt/blob.bin", f)
                elapsed = time.perf_counter() - t0
            assert got == len(data)
            return elapsed
        finally:
            os.unlink(tmp_path)

    par_s = min([await _par_run() for _ in range(2)])

    # Volume→device: ranged blocks land in a preallocated host buffer which
    # the device ingests directly (the weights-loader fast path)
    import jax.numpy as jnp

    host = bytearray(len(data))
    t0 = time.perf_counter()
    written = await vol.read_file_range_into("ckpt/blob.bin", 0, len(data), host)
    dev = jnp.asarray(np.frombuffer(host, np.uint8))
    dev.block_until_ready()
    dev_s = time.perf_counter() - t0
    assert written == len(data)
    assert np.array_equal(np.asarray(dev[:64]), np.frombuffer(data[:64], np.uint8))
    return {
        "volume_seq_gbps": round(len(data) / seq_s / 1e9, 3),
        "volume_parallel_gbps": round(len(data) / par_s / 1e9, 3),
        "volume_to_device_gbps": round(len(data) / dev_s / 1e9, 3),
        "volume_speedup": round(seq_s / par_s, 2),
    }


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--size-mb", type=int, default=1024, help="payload size per section (MiB)")
    parser.add_argument("--skip-volume", action="store_true")
    parser.add_argument("--skip-blob", action="store_true")
    args = parser.parse_args()

    result: dict = {"size_mb": args.size_mb}
    result.update(bench_serialization(args.size_mb))

    from modal_tpu._utils.async_utils import synchronizer
    from modal_tpu.client import _Client
    from modal_tpu.server.supervisor import LocalSupervisor

    state_dir = tempfile.mkdtemp(prefix="modal_tpu_dataplane_")
    sup = LocalSupervisor(num_workers=0, state_dir=state_dir)
    synchronizer.run(sup.start())
    os.environ["MODAL_TPU_SERVER_URL"] = sup.server_url
    _Client.set_env_client(None)
    try:
        if not args.skip_blob:
            result.update(synchronizer.run(_bench_blob(args.size_mb)))
        if not args.skip_volume:
            result.update(synchronizer.run(_bench_volume(args.size_mb)))
    finally:
        synchronizer.run(sup.stop())

    from modal_tpu.observability.metrics import REGISTRY

    summary = REGISTRY.bench_summary()
    if summary:
        result["metrics"] = summary
    result["peak_rss_gb"] = round(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6, 2)
    print("DATAPLANE_RESULT " + json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
