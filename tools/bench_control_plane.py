#!/usr/bin/env python
"""Sharded-control-plane bench (server/shards.py, docs/CONTROL_PLANE.md).

Boots an in-process ShardedSupervisor (director + N shards, real gRPC + the
client-side shard router), then drives a control-plane-shaped load:

- ``--calls`` concurrent function-call maps (FunctionMap + batched
  FunctionPutInputs) totalling ``--inputs`` inputs, spread across apps homed
  on every partition.  Shard schedulers are stopped so the numbers isolate
  the CONTROL plane — routing, handler, journal append — not container
  execution.
- mid-run, one shard is killed dead (``kill_shard`` = the in-process
  kill -9 analogue); the director's health loop fences it and a survivor
  replays its journal.  **takeover-to-first-placement** is measured as the
  wall time from the kill to the first post-kill input accepted on the dead
  shard's partition (the client rides UNAVAILABLE → map refresh → redial),
  and cross-checked against the takeover gauge on the successor's
  time-series store.

Reported (CONTROL_BENCH_RESULT JSON line):

- ``control_placement_p99_s`` / ``_p50_s`` — client-observed latency of one
  routed put-inputs RPC (placement = input accepted into shard state).
- ``control_calls_per_s`` — completed map-calls per second.
- ``control_inputs_per_s`` — accepted inputs per second.
- ``control_takeover_s`` — takeover-to-first-placement recovery time.
- ``federation_query_p50_s`` / ``federation_direct_p50_s`` /
  ``federation_overhead_x`` — fleet-merged /metrics/history query latency vs
  one shard's direct endpoint (ISSUE 17: merged must stay <= 2x direct at 3
  shards), plus ``flight_dump_s`` / ``flight_ring_bytes`` for the flight
  recorder's postmortem dump.
- ``journal_quorum_p50_s`` / ``journal_local_p50_s`` /
  ``journal_quorum_overhead_x`` — placement p50 with quorum journal
  replication on (MODAL_TPU_JOURNAL_REPLICAS=2) vs off (=0); the ISSUE 19
  bar is overhead <= 1.5x.  ``replica_takeover_s`` / ``replica_takeover_mode``
  time the dead-DISK takeover (shard killed + journal directory deleted;
  recovery must come from the survivors' replica streams).

Usage (full scale ≈ 1M inputs / 10k calls; scale down for CI):
    JAX_PLATFORMS=cpu python tools/bench_control_plane.py \
        [--inputs 1000000] [--calls 10000] [--shards 3] [--batch 100]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import shutil
import sys
import tempfile
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("MODAL_TPU_AUTO_LOCAL_SERVER", "0")


def _quantile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


async def _create_partition_apps(client, num_partitions: int):
    """One app+function per partition: app names are chosen so their crc32
    hash lands on each partition in turn (the creates route by name, the
    minted ids then pin everything downstream)."""
    import zlib

    from modal_tpu._utils.grpc_utils import retry_transient_errors
    from modal_tpu.proto import api_pb2

    functions = {}
    suffix = 0
    for part in range(num_partitions):
        while zlib.crc32(f"bench-cp-{suffix}".encode()) % num_partitions != part:
            suffix += 1
        name = f"bench-cp-{suffix}"
        suffix += 1
        app = await retry_transient_errors(
            client.stub.AppCreate, api_pb2.AppCreateRequest(description=name)
        )
        fn = await retry_transient_errors(
            client.stub.FunctionCreate,
            api_pb2.FunctionCreateRequest(
                app_id=app.app_id,
                function=api_pb2.Function(function_name="bench_fn"),
                tag="bench_fn",
            ),
        )
        functions[part] = fn.function_id
    return functions


async def _one_call(client, function_id: str, n_inputs: int, batch: int, payload: bytes,
                    latencies: list[float]) -> None:
    from modal_tpu._utils.grpc_utils import retry_transient_errors
    from modal_tpu.proto import api_pb2

    call = await retry_transient_errors(
        client.stub.FunctionMap,
        api_pb2.FunctionMapRequest(
            function_id=function_id, function_call_type=api_pb2.FUNCTION_CALL_TYPE_MAP
        ),
        max_retries=8,
    )
    idx = 0
    while idx < n_inputs:
        chunk = min(batch, n_inputs - idx)
        req = api_pb2.FunctionPutInputsRequest(
            function_id=function_id,
            function_call_id=call.function_call_id,
            inputs=[
                api_pb2.FunctionPutInputsItem(
                    idx=idx + k, input=api_pb2.FunctionInput(args=payload)
                )
                for k in range(chunk)
            ],
        )
        t0 = time.perf_counter()
        await retry_transient_errors(client.stub.FunctionPutInputs, req, max_retries=8)
        latencies.append(time.perf_counter() - t0)
        idx += chunk


async def _probe_recovery(client, function_id: str, t_kill: float, payload: bytes) -> float:
    """Hammer the dead partition with single-input placements until one lands
    — the client-observed takeover-to-first-placement time."""
    from modal_tpu._utils.grpc_utils import retry_transient_errors
    from modal_tpu.proto import api_pb2

    while True:
        try:
            call = await retry_transient_errors(
                client.stub.FunctionMap,
                api_pb2.FunctionMapRequest(
                    function_id=function_id,
                    function_call_type=api_pb2.FUNCTION_CALL_TYPE_MAP,
                ),
                max_retries=0,
                attempt_timeout=2.0,
            )
            await retry_transient_errors(
                client.stub.FunctionPutInputs,
                api_pb2.FunctionPutInputsRequest(
                    function_id=function_id,
                    function_call_id=call.function_call_id,
                    inputs=[api_pb2.FunctionPutInputsItem(
                        idx=0, input=api_pb2.FunctionInput(args=payload)
                    )],
                ),
                max_retries=0,
                attempt_timeout=2.0,
            )
            return time.monotonic() - t_kill
        except Exception:  # noqa: BLE001 — UNAVAILABLE until the takeover lands
            await asyncio.sleep(0.02)


async def _bench_federation(repeats: int = 20) -> dict:
    """Federation phase (ISSUE 17): merged /metrics/history query latency vs
    one shard's direct rendered `top` answer, plus the flight recorder's dump
    latency and serialized ring size.

    Runs against its OWN 3-shard subprocess fleet: the production deployment
    shape is one process per shard, so the fan-out's server-side work is
    genuinely concurrent. (An in-process fleet serializes all three handlers
    on one event loop, which turns overhead_x into a measure of the bench
    harness, not the federation.)"""
    from modal_tpu.observability import flight_recorder
    from modal_tpu.observability.federation import FederatedHistory
    from modal_tpu.server.shards import ShardedSupervisor

    fed_dir = tempfile.mkdtemp(prefix="bench-federation-")
    sup = ShardedSupervisor(
        num_shards=3,
        num_workers=3,
        state_dir=fed_dir,
        worker_chips=8,
        worker_tpu_type="local-sim",
        subprocess_shards=True,
        health_interval_s=5.0,
    )
    out: dict = {}
    try:
        await sup.start()
        await asyncio.sleep(2.5)  # let each shard's sampler populate its store
        fed = FederatedHistory(fed_dir, shared_registry=False)
        live = [s for s in fed.topology() if not s.get("dead")]
        if live:
            # the single-shard arm is what an operator runs against a
            # monolith: the shard's OWN rendered `top` answer over the same
            # transport — so overhead_x isolates the fan-out + merge cost
            await fed.payload("top")  # warm connections on both arms
            await fed._fetch(live[0], "top", 600.0)
            fed_lat: list[float] = []
            direct_lat: list[float] = []
            for _ in range(repeats):
                t0 = time.perf_counter()
                await fed.payload("top")
                fed_lat.append(time.perf_counter() - t0)
                t0 = time.perf_counter()
                await fed._fetch(live[0], "top", 600.0)
                direct_lat.append(time.perf_counter() - t0)
            # the merge itself (namespacing + fleet_summary + per-shard rows)
            # is the only work federation ADDS beyond the fetches — time it
            # separately so the additive cost is guarded host-independently
            snaps, missing, dead = await fed._gather(600.0)
            merge_lat: list[float] = []
            for _ in range(repeats):
                t0 = time.perf_counter()
                merged = fed.merged(snaps)
                fed._top_payload(snaps, missing, dead, merged, fed._fed_meta(snaps, missing, dead))
                merge_lat.append(time.perf_counter() - t0)
            await fed.close()
            fed_lat.sort()
            direct_lat.sort()
            merge_lat.sort()
            fp50 = _quantile(fed_lat, 0.5)
            dp50 = _quantile(direct_lat, 0.5)
            out.update(
                {
                    "federation_query_p50_s": round(fp50, 6),
                    "federation_query_p99_s": round(_quantile(fed_lat, 0.99), 6),
                    "federation_direct_p50_s": round(dp50, 6),
                    "federation_merge_p50_s": round(_quantile(merge_lat, 0.5), 6),
                    "federation_overhead_x": round(fp50 / dp50, 3) if dp50 > 0 else None,
                    "federation_shards": len(live),
                    # on a host with fewer cores than shards every fetch's
                    # client+server CPU serializes, so overhead_x floors at
                    # ~N regardless of transport — the guard reads this to
                    # pick the bar it can honestly hold
                    "federation_cores": os.cpu_count() or 1,
                }
            )
        fr = flight_recorder.FlightRecorder(
            os.path.join(fed_dir, "bench-flight"), scope="bench", interval_s=0.0
        )
        for _ in range(fr.samples.maxlen or 60):
            fr.record_sample()
        t0 = time.perf_counter()
        dump_path = fr.dump("bench")
        out["flight_dump_s"] = round(time.perf_counter() - t0, 6)
        out["flight_ring_bytes"] = os.path.getsize(dump_path) if dump_path else 0
    finally:
        await sup.stop()
        shutil.rmtree(fed_dir, ignore_errors=True)
    return out


async def _bench_replication(args) -> dict:
    """ISSUE 19 A/B: identical placement loads against two fresh in-process
    fleets — quorum journal replication ON (MODAL_TPU_JOURNAL_REPLICAS=2)
    vs OFF (=0, the byte-identical single-writer degrade). The acceptance
    bar is quorum p50 <= 1.5x local-only p50 on the same host. The ON fleet
    then loses a shard AND its journal directory (disk death, not process
    death) and the replica-stream takeover is timed."""
    from modal_tpu.client import _Client
    from modal_tpu.server.shards import ShardedSupervisor

    REPL_CALLS = 120
    REPL_INPUTS_PER_CALL = 20
    prior = os.environ.get("MODAL_TPU_JOURNAL_REPLICAS")
    metrics: dict = {}
    try:
        for env_value, key in (("0", "journal_local_p50_s"), ("2", "journal_quorum_p50_s")):
            os.environ["MODAL_TPU_JOURNAL_REPLICAS"] = env_value
            state_dir = tempfile.mkdtemp(prefix=f"bench-repl-{env_value}-")
            sup = ShardedSupervisor(
                num_shards=args.shards,
                num_workers=args.shards,
                state_dir=state_dir,
                worker_chips=8,
                worker_tpu_type="local-sim",
                health_interval_s=0.2,
            )
            await sup.start()
            for shard in sup.shards:
                if shard is not None:
                    await shard.scheduler.stop()
            client = _Client(sup.server_url, 1)
            await client._open()
            try:
                await client.hello()
                functions = await _create_partition_apps(client, args.shards)
                payload = b"x" * args.payload_bytes
                latencies: list[float] = []
                sem = asyncio.Semaphore(min(args.concurrency, 32))

                async def _guarded(part: int) -> None:
                    async with sem:
                        await _one_call(
                            client,
                            functions[part],
                            REPL_INPUTS_PER_CALL,
                            min(args.batch, REPL_INPUTS_PER_CALL),
                            payload,
                            latencies,
                        )

                await asyncio.gather(*(_guarded(i % args.shards) for i in range(REPL_CALLS)))
                latencies.sort()
                metrics[key] = round(_quantile(latencies, 0.50), 6)
                if env_value == "2":
                    # dead-disk takeover: kill the shard AND delete its journal
                    # — only the survivors' replica streams can rehydrate it
                    kill_index = 1 % args.shards
                    await sup.kill_shard(kill_index)
                    shutil.rmtree(
                        os.path.join(state_dir, f"shard-{kill_index}", "journal"),
                        ignore_errors=True,
                    )
                    deadline = time.monotonic() + 60.0
                    while time.monotonic() < deadline:
                        if sup.assignments[kill_index] != kill_index:
                            break
                        await asyncio.sleep(0.05)
                    entries = [
                        e for e in sup.takeover_log if e["dead_shard"] == kill_index
                    ]
                    if entries:
                        metrics["replica_takeover_s"] = entries[-1]["seconds"]
                        metrics["replica_takeover_mode"] = entries[-1]["mode"]
            finally:
                await client._close()
                await sup.stop()
                shutil.rmtree(state_dir, ignore_errors=True)
        local = metrics.get("journal_local_p50_s") or 0.0
        quorum = metrics.get("journal_quorum_p50_s") or 0.0
        if local > 0 and quorum > 0:
            metrics["journal_quorum_overhead_x"] = round(quorum / local, 3)
    finally:
        if prior is None:
            os.environ.pop("MODAL_TPU_JOURNAL_REPLICAS", None)
        else:
            os.environ["MODAL_TPU_JOURNAL_REPLICAS"] = prior
    return metrics


async def run_bench(args) -> dict:
    from modal_tpu.client import _Client
    from modal_tpu.server.shards import ShardedSupervisor

    # replication A/B first: its two small fleets must not share CPU with the
    # main load (quorum overhead is a latency ratio — contamination skews it)
    replication_metrics = await _bench_replication(args)
    state_dir = tempfile.mkdtemp(prefix="bench-control-")
    os.environ["MODAL_TPU_STATE_DIR"] = state_dir
    sup = ShardedSupervisor(
        num_shards=args.shards,
        num_workers=args.shards,
        state_dir=state_dir,
        worker_chips=8,
        worker_tpu_type="local-sim",
        health_interval_s=0.2,
    )
    await sup.start()
    # control-plane isolation: no container execution behind the handlers
    for shard in sup.shards:
        if shard is not None:
            await shard.scheduler.stop()
    client = _Client(sup.server_url, 1)
    await client._open()
    try:
        await client.hello()
        functions = await _create_partition_apps(client, args.shards)
        payload = b"x" * args.payload_bytes
        per_call = max(1, args.inputs // args.calls)
        latencies: list[float] = []
        sem = asyncio.Semaphore(args.concurrency)

        async def _guarded(part: int) -> None:
            async with sem:
                await _one_call(client, functions[part], per_call, args.batch,
                                payload, latencies)

        kill_index = 1 % args.shards
        calls_first = args.calls // 2
        t_start = time.perf_counter()
        await asyncio.gather(
            *(_guarded(i % args.shards) for i in range(calls_first))
        )
        # federation phase between the load halves (its own subprocess fleet;
        # the main in-process fleet is idle while it runs)
        federation_metrics = await _bench_federation()
        # kill one shard mid-run, keep pumping, and race the recovery probe
        t_kill = time.monotonic()
        await sup.kill_shard(kill_index)
        probe = asyncio.create_task(
            _probe_recovery(client, functions[kill_index], t_kill, payload)
        )
        await asyncio.gather(
            *(_guarded(i % args.shards) for i in range(args.calls - calls_first))
        )
        takeover_s = await probe
        total_s = time.perf_counter() - t_start

        latencies.sort()
        successor = sup.assignments[kill_index]
        gauge_takeover = None
        succ_sup = sup.shards[successor]
        if succ_sup is not None and succ_sup.state.timeseries is not None:
            stats = succ_sup.state.timeseries.gauge_stats(
                "modal_tpu_shard_takeover_seconds", 600.0
            )
            if stats:
                gauge_takeover = stats.get("last")
        return {
            "inputs": per_call * args.calls,
            "calls": args.calls,
            "shards": args.shards,
            "batch": args.batch,
            "payload_bytes": args.payload_bytes,
            "control_placement_p50_s": round(_quantile(latencies, 0.50), 6),
            "control_placement_p99_s": round(_quantile(latencies, 0.99), 6),
            "control_calls_per_s": round(args.calls / total_s, 2),
            "control_inputs_per_s": round(per_call * args.calls / total_s, 2),
            "control_takeover_s": round(takeover_s, 4),
            "takeover_gauge_s": gauge_takeover,
            "takeover_epoch": sup.epoch,
            "takeover_log": sup.takeover_log,
            "total_s": round(total_s, 2),
            **federation_metrics,
            **replication_metrics,
        }
    finally:
        await client._close()
        await sup.stop()
        shutil.rmtree(state_dir, ignore_errors=True)


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--inputs", type=int, default=1_000_000)
    parser.add_argument("--calls", type=int, default=10_000)
    parser.add_argument("--shards", type=int, default=3)
    parser.add_argument("--batch", type=int, default=100)
    parser.add_argument("--payload-bytes", type=int, default=64)
    parser.add_argument("--concurrency", type=int, default=256)
    args = parser.parse_args()
    # _Client's methods are synchronize_api-wrapped in place: from a foreign
    # asyncio loop they'd block instead of returning coroutines, so the whole
    # bench must run ON the synchronizer loop (same as bench_dataplane.py)
    from modal_tpu._utils.async_utils import synchronizer

    result = synchronizer.run(run_bench(args))
    print("CONTROL_BENCH_RESULT " + json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
