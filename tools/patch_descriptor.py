"""Regenerate modal_tpu/proto/api_pb2.py WITHOUT protoc.

This image ships no protoc / grpcio-tools, so proto/regen.sh cannot run here.
This tool covers the common case — adding scalar fields to existing messages —
by mutating the checked-in serialized FileDescriptorProto directly:

1. load the current api_pb2.py and pull DESCRIPTOR.serialized_pb
2. parse it as a FileDescriptorProto
3. apply the field additions declared in PATCHES (idempotent: fields already
   present are skipped, so the tool can re-run safely)
4. rewrite api_pb2.py around the new serialized blob

api.proto remains the human-readable source of truth; keep PATCHES in sync
with it. When protoc is available, `proto/regen.sh` supersedes this tool.

Usage: python tools/patch_descriptor.py
"""

from __future__ import annotations

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from google.protobuf import descriptor_pb2  # noqa: E402

F = descriptor_pb2.FieldDescriptorProto

# New messages: name -> [(field_name, number, type, label, type_name)].
# type_name is only used for TYPE_MESSAGE fields (fully-qualified, leading
# dot). Idempotent like PATCHES: an existing message of the same name is
# verified field-by-field instead of re-added.
NEW_MESSAGES: dict[str, list[tuple[str, int, int, int, str]]] = {
    # Warm-pool cold starts (server/warm_pool.py, docs/COLDSTART.md):
    # scheduler→worker directive to keep N pre-forked interpreters parked
    # for an image (rides WorkerPollResponse outside the event oneof).
    "PoolDirective": [
        ("image_id", 1, F.TYPE_STRING, F.LABEL_OPTIONAL, ""),
        ("target", 2, F.TYPE_INT32, F.LABEL_OPTIONAL, ""),
    ],
    # Parked interpreter → worker router long-poll: "give me my next
    # ContainerArguments". Token is per pool entry (issued at spawn).
    "PoolAwaitRequest": [
        ("pool_id", 1, F.TYPE_STRING, F.LABEL_OPTIONAL, ""),
        ("token", 2, F.TYPE_STRING, F.LABEL_OPTIONAL, ""),
        ("generation", 3, F.TYPE_INT32, F.LABEL_OPTIONAL, ""),
        ("pid", 4, F.TYPE_INT64, F.LABEL_OPTIONAL, ""),
        ("timeout", 5, F.TYPE_FLOAT, F.LABEL_OPTIONAL, ""),
    ],
    # The handoff payload: args path + env delta to apply in-process (the
    # restore-without-re-exec contract; env_set_json replaces/extends,
    # env_unset removes pool-spawn-only keys).
    "PoolAwaitResponse": [
        ("has_task", 1, F.TYPE_BOOL, F.LABEL_OPTIONAL, ""),
        ("task_id", 2, F.TYPE_STRING, F.LABEL_OPTIONAL, ""),
        ("args_path", 3, F.TYPE_STRING, F.LABEL_OPTIONAL, ""),
        ("env_set_json", 4, F.TYPE_STRING, F.LABEL_OPTIONAL, ""),
        ("env_unset", 5, F.TYPE_STRING, F.LABEL_REPEATED, ""),
        ("evict", 6, F.TYPE_BOOL, F.LABEL_OPTIONAL, ""),
        ("handoff_id", 7, F.TYPE_STRING, F.LABEL_OPTIONAL, ""),
    ],
    # Interpreter-side delivery ack: the worker only commits the adoption
    # (and skips the fresh-spawn fallback) once this lands — a parked
    # process killed mid-handoff never acks, so the task falls back.
    "PoolAdoptAckRequest": [
        ("pool_id", 1, F.TYPE_STRING, F.LABEL_OPTIONAL, ""),
        ("token", 2, F.TYPE_STRING, F.LABEL_OPTIONAL, ""),
        ("handoff_id", 3, F.TYPE_STRING, F.LABEL_OPTIONAL, ""),
        ("task_id", 4, F.TYPE_STRING, F.LABEL_OPTIONAL, ""),
    ],
    "PoolAdoptAckResponse": [],
    # Continuous profiling (ISSUE 7, observability/profiler.py): runtime
    # toggle for the in-process sampling profiler. action: start|stop|status;
    # the response lists the folded-stack files currently on disk so the CLI
    # can render `profile show` right after a stop.
    "ProfileControlRequest": [
        ("action", 1, F.TYPE_STRING, F.LABEL_OPTIONAL, ""),
        ("hz", 2, F.TYPE_FLOAT, F.LABEL_OPTIONAL, ""),
    ],
    "ProfileControlResponse": [
        ("running", 1, F.TYPE_BOOL, F.LABEL_OPTIONAL, ""),
        ("supervisor_profile_path", 2, F.TYPE_STRING, F.LABEL_OPTIONAL, ""),
        ("n_samples", 3, F.TYPE_INT64, F.LABEL_OPTIONAL, ""),
        ("profile_paths", 4, F.TYPE_STRING, F.LABEL_REPEATED, ""),
    ],
    # Coalesced dispatch (ISSUE 8, _utils/coalescer.py): N concurrent
    # `.remote()`s submitted within one adaptive window share ONE RPC — each
    # sub-request is handled exactly like a standalone FunctionMap (own call
    # id, own journal records), the batch is just the wire vehicle.
    "FunctionMapBatchRequest": [
        ("requests", 1, F.TYPE_MESSAGE, F.LABEL_REPEATED, ".modal.tpu.api.FunctionMapRequest"),
    ],
    "FunctionMapBatchResponse": [
        ("responses", 1, F.TYPE_MESSAGE, F.LABEL_REPEATED, ".modal.tpu.api.FunctionMapResponse"),
    ],
    # Same coalescing vehicle for the input-plane unary path: N concurrent
    # AttemptStarts share one RPC, each minting its own call + attempt token.
    "AttemptStartBatchRequest": [
        ("requests", 1, F.TYPE_MESSAGE, F.LABEL_REPEATED, ".modal.tpu.api.AttemptStartRequest"),
    ],
    "AttemptStartBatchResponse": [
        ("responses", 1, F.TYPE_MESSAGE, F.LABEL_REPEATED, ".modal.tpu.api.AttemptStartResponse"),
    ],
    # Dispatch-floor lever (ISSUE 9 satellite, docs/DISPATCH.md): the
    # container's output publication and next-input claim share ONE RPC —
    # the server applies `put` (same journal group-commit + (input_id,
    # retry_count) dedupe as FunctionPutOutputs), then runs the
    # FunctionGetInputs long-poll for `get`. Response reuses
    # FunctionGetInputsResponse, so the claim path is wire-identical.
    "FunctionExchangeRequest": [
        ("put", 1, F.TYPE_MESSAGE, F.LABEL_OPTIONAL, ".modal.tpu.api.FunctionPutOutputsRequest"),
        ("get", 2, F.TYPE_MESSAGE, F.LABEL_OPTIONAL, ".modal.tpu.api.FunctionGetInputsRequest"),
    ],
    # Fleet SLO observability (ISSUE 11, observability/timeseries.py +
    # slo.py): windowed history / alert / dashboard queries against the
    # supervisor-resident time-series store. The response is JSON (like the
    # heartbeat's telemetry_json): the payload shapes are library-defined and
    # evolve faster than the wire — query names: describe | series |
    # quantile | alerts | top. Journal-EXEMPT: history is runtime-transient,
    # rebuilt by sampling.
    "MetricsHistoryRequest": [
        ("query", 1, F.TYPE_STRING, F.LABEL_OPTIONAL, ""),
        ("family", 2, F.TYPE_STRING, F.LABEL_OPTIONAL, ""),
        ("window_s", 3, F.TYPE_FLOAT, F.LABEL_OPTIONAL, ""),
        ("q", 4, F.TYPE_FLOAT, F.LABEL_OPTIONAL, ""),
    ],
    "MetricsHistoryResponse": [
        ("payload_json", 1, F.TYPE_STRING, F.LABEL_OPTIONAL, ""),
    ],
    # Sharded control plane (ISSUE 16, server/shards.py): director-internal
    # administration of supervisor shards — health probes, partition takeover
    # orchestration, and epoch fencing of stale shards rejoining after a
    # takeover. Never journaled (runtime topology, rebuilt by the director's
    # health loop); action: status | adopt | fence.
    "ShardControlRequest": [
        ("action", 1, F.TYPE_STRING, F.LABEL_OPTIONAL, ""),
        ("partition", 2, F.TYPE_INT32, F.LABEL_OPTIONAL, ""),
        ("journal_dir", 3, F.TYPE_STRING, F.LABEL_OPTIONAL, ""),
        ("epoch", 4, F.TYPE_INT64, F.LABEL_OPTIONAL, ""),
        ("shard_index", 5, F.TYPE_INT32, F.LABEL_OPTIONAL, ""),
    ],
    "ShardControlResponse": [
        ("payload_json", 1, F.TYPE_STRING, F.LABEL_OPTIONAL, ""),
    ],
    # Quorum journal replication (ISSUE 19, server/replication.py): a
    # writer shard streams its journal appends to follower shards; every
    # message carries the writer's fleet epoch as a fencing token. kind:
    # append (payload_json = list of record lines) | snapshot (payload_json
    # = compacted snapshot lines, base_seq = covered seq) | seal (fence the
    # stream at its replicated max-seq under a takeover epoch) | status.
    # The response payload is JSON ({ok, last_seq, epoch, error}) — the
    # shape evolves with the protocol, like ShardControl's.
    # incarnation/boot_seq identify the writer PROCESS generation: the
    # incarnation counter bumps durably on every journal open, and boot_seq
    # is the seq the restarted writer replayed to — a follower seeing a new
    # incarnation truncates any tail past boot_seq (records the crashed
    # writer buffered to us but lost locally), so streams cannot silently
    # diverge across a writer crash-restart.
    "JournalReplicateRequest": [
        ("kind", 1, F.TYPE_STRING, F.LABEL_OPTIONAL, ""),
        ("writer_shard", 2, F.TYPE_INT32, F.LABEL_OPTIONAL, ""),
        ("epoch", 3, F.TYPE_INT64, F.LABEL_OPTIONAL, ""),
        ("base_seq", 4, F.TYPE_INT64, F.LABEL_OPTIONAL, ""),
        ("payload_json", 5, F.TYPE_STRING, F.LABEL_OPTIONAL, ""),
        ("incarnation", 6, F.TYPE_INT64, F.LABEL_OPTIONAL, ""),
        ("boot_seq", 7, F.TYPE_INT64, F.LABEL_OPTIONAL, ""),
    ],
    "JournalReplicateResponse": [
        ("payload_json", 1, F.TYPE_STRING, F.LABEL_OPTIONAL, ""),
    ],
}

# (message, field_name, field_number, field_type) — optionally a 5-tuple with
# a fully-qualified type_name for TYPE_MESSAGE fields.
PATCHES: list[tuple[str, str, int, int]] = [
    ("FunctionGetInputsItem", "resume_token", 7, F.TYPE_STRING),
    ("ContainerCheckpointRequest", "input_id", 3, F.TYPE_STRING),
    ("ContainerCheckpointRequest", "resume_token", 4, F.TYPE_STRING),
    ("TaskStopEvent", "preempt", 4, F.TYPE_BOOL),
    ("TaskStopEvent", "grace_s", 5, F.TYPE_FLOAT),
    ("WorkerHeartbeatRequest", "draining", 3, F.TYPE_BOOL),
    ("WorkerHeartbeatRequest", "drain_grace_s", 4, F.TYPE_FLOAT),
    # distributed tracing (observability/tracing.py): the enqueue-time trace
    # context rides the input to the container so its spans stitch in
    ("FunctionGetInputsItem", "trace_context", 8, F.TYPE_STRING),
    # zero-copy data plane: volume block reads ride the blob server's HTTP
    # Range plane when the store advertises it (empty = gRPC block fetch)
    ("VolumeGetFile2Response", "block_url_base", 3, F.TYPE_STRING),
    # co-located stores (local supervisor / worker on the storage host)
    # advertise the block dir so clients pread straight from page cache;
    # clients verify the dir exists before trusting it
    ("VolumeGetFile2Response", "block_local_dir", 4, F.TYPE_STRING),
    # durable control plane (server/journal.py): a heartbeat from a worker id
    # the control plane doesn't know (e.g. restarted without its journal)
    # answers with reannounce=true — the worker re-registers under its old id
    # instead of hammering an id that will never exist again
    ("WorkerHeartbeatResponse", "reannounce", 1, F.TYPE_BOOL),
    # Warm-pool cold starts (ISSUE 5): the entrypoint marks a placement that
    # was served by a pre-forked parked interpreter (handoff, no re-exec)
    ("ContainerHelloRequest", "warm_pool_hit", 3, F.TYPE_BOOL),
    # surfaced on the timeline so bench.py can PROVE the measured cold start
    # went through the warm pool (acceptance: warm_pool_hit field)
    ("TaskTimeline", "warm_pool_hit", 7, F.TYPE_BOOL),
    # workers report parked-interpreter inventory; the scheduler prefers
    # warm workers on placement ties
    ("WorkerHeartbeatRequest", "warm_pool_ready", 5, F.TYPE_INT32),
    # scheduler→worker pool-sizing directive (outside the event oneof; the
    # worker checks HasField)
    ("WorkerPollResponse", "pool_directive", 4, F.TYPE_MESSAGE, ".modal.tpu.api.PoolDirective"),
    # Continuous profiling (ISSUE 7): the supervisor repeats the active
    # profile command ("start:<hz>" | "stop") on every container heartbeat —
    # idempotent apply in io_manager, so no ack protocol is needed
    ("ContainerHeartbeatResponse", "profile_command", 2, F.TYPE_STRING),
    # Device/compile telemetry push (observability/device_telemetry.py): the
    # container's whitelisted metric families (device memory gauges, compile
    # events/durations, step times) ride the heartbeat as compact JSON; the
    # control plane merges deltas into its own registry so GET /metrics
    # shows LIVE per-device HBM and compile activity
    ("ContainerHeartbeatRequest", "telemetry_json", 3, F.TYPE_STRING),
    # Critical-path attribution (observability/critical_path.py): the server
    # stamps claim time on each delivered input so the container's
    # container.input_deliver span starts at the CLAIM — anchoring at the
    # long-poll's issue time would swallow the client's prep/RPC window
    ("FunctionGetInputsItem", "claimed_at", 9, F.TYPE_DOUBLE),
    # Local fast-path transport (ISSUE 8, docs/DISPATCH.md): co-located
    # clients learn the control/input-plane Unix-domain sockets and the
    # on-disk blob store at handshake; a client that can stat the paths dials
    # UDS (or reads blobs straight from page cache) instead of TCP/HTTP, and
    # falls back the moment the paths stop resolving
    ("ClientHelloResponse", "uds_path", 5, F.TYPE_STRING),
    ("ClientHelloResponse", "input_plane_uds_path", 6, F.TYPE_STRING),
    ("ClientHelloResponse", "blob_local_dir", 7, F.TYPE_STRING),
    # Serving-tier SLO autoscaling (ISSUE 9, docs/SERVING.md): web/serving
    # functions have no input backlog to scale on, so the scheduler sizes
    # them from the serving telemetry containers push over heartbeats —
    # scale up while pushed p95 TTFT exceeds target_ttft_ms, scale down
    # while per-replica tokens/s sits far under target_tokens_per_replica
    ("AutoscalerSettings", "target_ttft_ms", 5, F.TYPE_FLOAT),
    ("AutoscalerSettings", "target_tokens_per_replica", 6, F.TYPE_FLOAT),
    # Sharded control plane (ISSUE 16, server/shards.py): the placement
    # director answers ClientHello with the partition→shard-URL map as JSON
    # ({"epoch": N, "urls": ["grpc://...", ...]} indexed by partition — the
    # JSON idiom matches telemetry_json/payload_json: the map shape evolves
    # faster than the wire). Empty on monolith supervisors, so existing
    # clients see no behavior change. shard_epoch fences stale maps: a client
    # holding an older epoch re-hellos before trusting a routing miss.
    ("ClientHelloResponse", "shard_map_json", 8, F.TYPE_STRING),
    ("ClientHelloResponse", "shard_epoch", 9, F.TYPE_INT64),
]

HEADER = '''\
# -*- coding: utf-8 -*-
# Generated by the protocol buffer compiler.  DO NOT EDIT!
# source: api.proto
# (regenerated by tools/patch_descriptor.py — protoc is unavailable in this
# environment; the serialized descriptor is patched in place)
"""Generated protocol buffer code."""
from google.protobuf.internal import builder as _builder
from google.protobuf import descriptor as _descriptor
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf import symbol_database as _symbol_database
# @@protoc_insertion_point(imports)

_sym_db = _symbol_database.Default()


DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile({blob!r})

_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())
_builder.BuildTopDescriptorsAndMessages(DESCRIPTOR, 'api_pb2', globals())
# @@protoc_insertion_point(module_scope)
'''


def _json_name(name: str) -> str:
    parts = name.split("_")
    return parts[0] + "".join(p.capitalize() for p in parts[1:])


def _load_pb2(pb2_path: str):
    """Load api_pb2 straight from its file, NOT through the modal_tpu
    package: the package import builds the RPC registry, which validates
    every registered RPC against the descriptor — a registry entry for the
    very message this tool is about to add would deadlock the regen."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("_patch_descriptor_api_pb2", pb2_path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def main() -> None:
    pb2_path = os.path.join(REPO_ROOT, "modal_tpu", "proto", "api_pb2.py")
    api_pb2 = _load_pb2(pb2_path)

    fdp = descriptor_pb2.FileDescriptorProto.FromString(api_pb2.DESCRIPTOR.serialized_pb)
    by_name = {m.name: m for m in fdp.message_type}
    changed = 0
    for msg_name, fields in NEW_MESSAGES.items():
        msg = by_name.get(msg_name)
        if msg is None:
            msg = fdp.message_type.add(name=msg_name)
            by_name[msg_name] = msg
            changed += 1
        existing = {f.name: f for f in msg.field}
        for field_name, number, ftype, label, type_name in fields:
            if field_name in existing:
                f = existing[field_name]
                if f.number != number or f.type != ftype:
                    raise SystemExit(
                        f"{msg_name}.{field_name} exists with number={f.number} type={f.type}; "
                        f"patch wants number={number} type={ftype}"
                    )
                continue
            if any(f.number == number for f in msg.field):
                raise SystemExit(f"{msg_name} field number {number} already taken")
            kwargs = dict(
                name=field_name,
                number=number,
                type=ftype,
                label=label,
                json_name=_json_name(field_name),
            )
            if type_name:
                kwargs["type_name"] = type_name
            msg.field.add(**kwargs)
            changed += 1
    for patch in PATCHES:
        msg_name, field_name, number, ftype = patch[:4]
        type_name = patch[4] if len(patch) > 4 else ""
        msg = by_name.get(msg_name)
        if msg is None:
            raise SystemExit(f"message {msg_name} not found in descriptor")
        existing = {f.name: f for f in msg.field}
        if field_name in existing:
            f = existing[field_name]
            if f.number != number or f.type != ftype:
                raise SystemExit(
                    f"{msg_name}.{field_name} exists with number={f.number} type={f.type}; "
                    f"patch wants number={number} type={ftype}"
                )
            continue
        if any(f.number == number for f in msg.field):
            raise SystemExit(f"{msg_name} field number {number} already taken")
        kwargs = dict(
            name=field_name,
            number=number,
            type=ftype,
            label=F.LABEL_OPTIONAL,
            json_name=_json_name(field_name),
        )
        if type_name:
            kwargs["type_name"] = type_name
        msg.field.add(**kwargs)
        changed += 1
    if not changed:
        print("descriptor already up to date")
        return
    blob = fdp.SerializeToString()
    with open(pb2_path, "w") as f:
        f.write(HEADER.format(blob=blob))
    print(f"patched {changed} field(s); rewrote {pb2_path} ({len(blob)} descriptor bytes)")


if __name__ == "__main__":
    main()
