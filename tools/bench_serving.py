"""Serving-tier load generator: many concurrent SSE clients vs the
sequential baseline.

ISSUE 9 acceptance artifact: under >=32 concurrent clients the continuous-
batching engine must deliver >=2x `tokens/s/chip` over the sequential
`greedy_generate` baseline on the tiny config (CPU fallback), with p99 TTFT
reported and the first SSE token observed BEFORE generation completes.

What it runs:

1. **baseline** — `sampling.greedy_generate` batch=1, one request at a time
   (the pre-serving path: a queue of `.remote()`s decoding serially).
2. **serving** — a `ServingEngine` behind the real ASGI HTTP server
   (runtime/asgi.py AsgiHttpServer — the same server a container uses), hit
   by N concurrent socket clients speaking `POST /v1/generate` with
   `stream: true`; client-side timestamps give TTFT per request.

Prints ONE line: SERVING_BENCH_RESULT {json}; bench.py folds the fields in
as ``serving_*`` and tolerance-checks them against BENCH_serving.json (same
>1.5x discipline as the dispatch floor guard).

Run directly: JAX_PLATFORMS=cpu python tools/bench_serving.py [--clients 32]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import socket
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

PROMPT_LEN = 12
GEN_LEN = 32


def _quantile(vals: list[float], q: float) -> float:
    # the one quantile contract (observability/quantile.py, ISSUE 11)
    from modal_tpu.observability.quantile import quantile as shared_quantile

    return shared_quantile(sorted(vals), q)


def _baseline_tokens_per_s(params, cfg, prompts, warmup: int = 1) -> float:
    """Sequential batch=1 greedy decode — the pre-serving throughput."""
    import jax.numpy as jnp

    from modal_tpu.models.sampling import greedy_generate

    def run_one(prompt) -> None:
        out = greedy_generate(
            params, cfg, jnp.asarray([prompt], jnp.int32), GEN_LEN, cache_len=cfg.max_seq_len
        )
        out.block_until_ready()

    for p in prompts[:warmup]:
        run_one(p)  # compile prefill + fused decode chunks
    t0 = time.perf_counter()
    for p in prompts:
        run_one(p)
    wall = time.perf_counter() - t0
    return len(prompts) * GEN_LEN / wall


class _SSEClient:
    """Minimal blocking SSE client over a raw socket (no deps; reads the
    exact bytes the server framed, so first-token timing is honest)."""

    def __init__(self, port: int):
        self.port = port

    def generate_stream(self, prompt: list[int], request_id: str) -> dict:
        payload = json.dumps(
            {"prompt": prompt, "max_new_tokens": GEN_LEN, "stream": True, "request_id": request_id}
        ).encode()
        t_submit = time.perf_counter()
        s = socket.create_connection(("127.0.0.1", self.port), timeout=300)
        try:
            s.sendall(
                b"POST /v1/generate HTTP/1.1\r\nhost: bench\r\ncontent-type: application/json\r\n"
                + f"content-length: {len(payload)}\r\n\r\n".encode()
                + payload
            )
            buf = b""
            t_first = None
            tokens: list[int] = []
            done = False
            while True:
                chunk = s.recv(65536)
                if not chunk:
                    break
                buf += chunk
                while b"\n\n" in buf:
                    event, buf = buf.split(b"\n\n", 1)
                    text = event.decode("utf-8", "replace")
                    if "event: token" in text:
                        if t_first is None:
                            t_first = time.perf_counter()
                        for line in text.splitlines():
                            if line.startswith("data: "):
                                tokens.append(json.loads(line[6:])["token"])
                    elif "event: done" in text:
                        done = True
                if done:
                    break
        finally:
            s.close()
        t_done = time.perf_counter()
        return {
            "ttft_s": (t_first - t_submit) if t_first is not None else None,
            "wall_s": t_done - t_submit,
            "tokens": tokens,
            "done": done,
            # the streaming acceptance: the first token landed strictly
            # before the request's generation completed
            "first_token_before_completion": (
                t_first is not None and done and t_first < t_done - 1e-4
            ),
        }


def _run_serving_load(
    params, cfg, prompts, clients: int, label: str,
    num_pages: int = 0, prime=None,
) -> dict:
    """One continuous-batching load phase behind the real ASGI server:
    N concurrent SSE clients drain every prompt. Returns outs/wall/stats.
    `prime` (a list of prompts) is generated sequentially before the timed
    window — the shared-prefix phase uses it to make the fleet prompt
    cache-resident AND to compile the hit path (suffix-bucket prefill +
    copy_page) outside the measurement."""
    import asyncio
    import threading
    from concurrent.futures import ThreadPoolExecutor

    from modal_tpu.runtime.asgi import AsgiHttpServer
    from modal_tpu.serving.api import serving_asgi_app
    from modal_tpu.serving.engine import ServingEngine

    pool_pages = num_pages or (clients * ((PROMPT_LEN + GEN_LEN) // 16 + 2) + 8)
    engine = ServingEngine(
        params,
        cfg,
        max_slots=clients,
        num_pages=pool_pages,
        page_size=16,
        prefill_chunk=64,
    ).start()
    loop = asyncio.new_event_loop()
    threading.Thread(target=loop.run_forever, daemon=True).start()
    server = AsgiHttpServer(serving_asgi_app(engine))
    asyncio.run_coroutine_threadsafe(server.start(), loop).result(30)
    client = _SSEClient(server.port)
    try:
        # warmup: compile the prefill bucket + the max_slots decode executable
        for w_i, w_prompt in enumerate(prime or [prompts[0]]):
            warm = client.generate_stream(w_prompt, f"warmup-{label}-{w_i}")
            assert warm["done"] and len(warm["tokens"]) == GEN_LEN, warm
        t0 = time.perf_counter()
        with ThreadPoolExecutor(max_workers=clients) as pool:
            outs = list(
                pool.map(
                    lambda iv: client.generate_stream(iv[1], f"{label}-{iv[0]}"),
                    enumerate(prompts),
                )
            )
        wall = time.perf_counter() - t0
    finally:
        asyncio.run_coroutine_threadsafe(server.stop(), loop).result(10)
        loop.call_soon_threadsafe(loop.stop)
    stats = engine.stats()
    engine.stop()
    return {"outs": outs, "wall": wall, "stats": stats}


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--clients", type=int, default=32, help="concurrent SSE clients")
    parser.add_argument("--requests", type=int, default=64, help="total requests")
    parser.add_argument("--baseline-requests", type=int, default=8)
    args = parser.parse_args()

    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("MODAL_TPU_JAX_PLATFORM", "cpu")

    import jax
    import numpy as np

    from modal_tpu.models.llama import get_config, init_params

    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(7)
    prompts = [
        rng.integers(0, cfg.vocab_size, size=PROMPT_LEN).tolist() for _ in range(args.requests)
    ]
    n_chips = max(1, jax.device_count()) if jax.default_backend() != "cpu" else 1

    result: dict = {"clients": args.clients, "requests": args.requests, "gen_len": GEN_LEN}

    # --- phase 1: sequential baseline ------------------------------------
    base_tps = _baseline_tokens_per_s(params, cfg, prompts[: args.baseline_requests])
    result["baseline_tokens_per_s_per_chip"] = round(base_tps / n_chips, 1)
    print(f"bench[serving]: baseline {base_tps:.0f} tokens/s (batch=1 sequential)", file=sys.stderr)

    # --- phase 2: continuous batching behind the real ASGI server --------
    # observability OFF: no trace sink, per-request timeline spans disabled —
    # the clean side of the ISSUE 11 overhead A/B
    os.environ["MODAL_TPU_SERVING_SPANS"] = "0"
    phase2 = _run_serving_load(params, cfg, prompts, args.clients, "bench")
    outs, wall, stats = phase2["outs"], phase2["wall"], phase2["stats"]

    bad = [o for o in outs if not o["done"] or len(o["tokens"]) != GEN_LEN]
    if bad:
        print(f"bench[serving]: {len(bad)} incomplete responses", file=sys.stderr)
    ttfts = [o["ttft_s"] for o in outs if o["ttft_s"] is not None]
    total_tokens = sum(len(o["tokens"]) for o in outs)
    serving_tps = total_tokens / wall

    result.update(
        {
            "tokens_per_s_per_chip": round(serving_tps / n_chips, 1),
            "speedup_vs_sequential": round(serving_tps / max(1e-9, base_tps), 2),
            "requests_per_s": round(len(outs) / wall, 2),
            "p50_ttft_s": round(_quantile(ttfts, 0.5), 4),
            "p99_ttft_s": round(_quantile(ttfts, 0.99), 4),
            "first_sse_token_before_completion": all(
                o["first_token_before_completion"] for o in outs
            ),
            "incomplete_responses": len(bad),
            "engine_steps": stats["steps"],
            "kv_pages_high_water": stats["kv_pages_high_water"],
            "kv_pages_total": stats["kv_pages_total"],
            "kv_pool_mb": round(stats["kv_pool_bytes"] / 1e6, 2),
            "preemptions": stats["preemptions"],
        }
    )
    print(
        f"bench[serving]: {serving_tps:.0f} tokens/s over {args.clients} clients "
        f"({result['speedup_vs_sequential']}x sequential), "
        f"TTFT p50 {result['p50_ttft_s']}s p99 {result['p99_ttft_s']}s",
        file=sys.stderr,
    )

    # --- phase 3: observability-overhead A/B (ISSUE 11 satellite) ---------
    # The SAME load with the full observability stack ON: per-request
    # timeline spans into a real trace sink + the supervisor-style
    # time-series sampler + SLO evaluation on cadence. Guarded acceptance:
    # observability must cost <= 2% tokens/s (BENCH_serving.json), and the
    # serving attribution's gap residue must stay <= 10%.
    #
    # Honest A/B on a noisy CPU host: interleaved on/off blocks with per-arm
    # MEDIANS (the bench_dispatch profiler-A/B pattern) — a single warm pair
    # measured ±7% run-to-run drift here, far too coarse for a 2% budget.
    # Every block is warm (the headline phase compiled everything); ordering
    # noise hits both arms symmetrically.
    import tempfile
    import threading

    from modal_tpu.observability import critical_path as cp, tracing
    from modal_tpu.observability.slo import SLOEvaluator
    from modal_tpu.observability.timeseries import TimeSeriesStore

    trace_dir = tempfile.mkdtemp(prefix="serving_obs_traces_")
    tracing.configure(trace_dir)
    store = TimeSeriesStore(interval_s=1.0)
    evaluator = SLOEvaluator(store)
    sample_walls: list[float] = []
    stop_evt = threading.Event()

    def _sampler() -> None:
        while not stop_evt.is_set():
            t0 = time.perf_counter()
            store.sample()
            evaluator.evaluate()
            sample_walls.append(time.perf_counter() - t0)
            stop_evt.wait(1.0)

    # the sampler runs across BOTH arms: its own cost must show up in the
    # "on" arm only via the spans; steady registry sampling is part of the
    # supervisor either way. Spans are the per-request cost being measured.
    sampler_thread = threading.Thread(target=_sampler, daemon=True)
    sampler_thread.start()
    off_tps: list[float] = []
    on_tps: list[float] = []
    block_prompts = prompts[: max(8, len(prompts) // 2)]
    try:
        for i in range(6):
            on = i % 2 == 1
            os.environ["MODAL_TPU_SERVING_SPANS"] = "1" if on else "0"
            block = _run_serving_load(
                params, cfg, block_prompts, args.clients, f"{'obs' if on else 'ref'}{i}"
            )
            tps = sum(len(o["tokens"]) for o in block["outs"]) / block["wall"]
            (on_tps if on else off_tps).append(tps)
    finally:
        stop_evt.set()
        sampler_thread.join(5)
        os.environ["MODAL_TPU_SERVING_SPANS"] = "1"
    ref_tps = _quantile(off_tps, 0.5)
    obs_tps = _quantile(on_tps, 0.5)
    overhead_pct = 100.0 * (ref_tps - obs_tps) / max(1e-9, ref_tps)
    # the off-arm's own block-to-block spread IS this host's measurement
    # noise floor: an overhead claim below it is unresolvable, and the
    # regression guard must not flag noise as a regression
    noise_floor_pct = 100.0 * (max(off_tps) - min(off_tps)) / max(1e-9, ref_tps)
    result["reference_tokens_per_s_per_chip"] = round(ref_tps / n_chips, 1)
    result["observability_tokens_per_s_per_chip"] = round(obs_tps / n_chips, 1)
    result["observability_overhead_pct"] = round(overhead_pct, 2)
    result["observability_noise_floor_pct"] = round(noise_floor_pct, 2)

    # serving attribution over the phase's per-request timelines: TTFT and
    # per-token latency decomposed into queue/prefill/decode/stream with the
    # gap residue reported honestly (`app attribute --serving` acceptance)
    agg, per_trace = cp.attribute_store(trace_dir, "", serving=True)
    print(cp.format_attribution_table(agg), file=sys.stderr)
    result["attribution_requests"] = agg.get("calls", 0)
    result["attribution_gap_share"] = round(agg.get("gap_share", 1.0), 4)
    result["attribution"] = {
        seg: round(v["p50_s"], 5) for seg, v in agg.get("segments", {}).items()
    }

    # slo_* / timeseries_* fields (bench.py folds these unprefixed)
    slo_payload = evaluator.payload()
    firing = [n for n, a in evaluator.alerts.items() if a.get("state") == "firing"]
    result["slo_rules_evaluated"] = len(slo_payload["rules"])
    result["slo_alerts_firing"] = len(firing)
    for r in slo_payload["rules"]:
        if r["rule"] == "serving_ttft_p95" and r.get("fast_burn") is not None:
            result["slo_ttft_fast_burn"] = round(r["fast_burn"], 3)
    result["timeseries_samples"] = store.samples_taken
    result["timeseries_points"] = sum(store.point_counts().values())
    if sample_walls:
        result["timeseries_sample_p50_s"] = round(_quantile(sample_walls, 0.5), 6)
    print(
        f"bench[serving]: observability A/B {obs_tps:.0f} (on) vs {ref_tps:.0f} (off, warm) "
        f"tokens/s ({overhead_pct:+.1f}% overhead), attribution gap "
        f"{result['attribution_gap_share'] * 100:.1f}% over {agg.get('calls', 0)} requests",
        file=sys.stderr,
    )

    # --- phase 4: shared-prefix workload (ISSUE 12) -----------------------
    # 32 clients, ONE long system prompt + short unique suffixes — the
    # "millions of users, one prefix" shape. A/B: prefix cache on vs off,
    # same pool/geometry; the cache is primed by one untimed request (steady
    # state: a fleet prompt is resident). Acceptance: >= 1.5x p50 TTFT.
    sys_prompt = rng.integers(0, cfg.vocab_size, size=96).tolist()
    shared_prompts = [
        sys_prompt + rng.integers(0, cfg.vocab_size, size=4).tolist()
        for _ in range(args.requests)
    ]
    prefix_ttfts: dict = {}
    prefix_stats: dict = {}
    for arm, enabled in (("off", False), ("on", True)):
        os.environ["MODAL_TPU_SERVING_PREFIX_CACHE"] = "1" if enabled else "0"
        # two primes: the first makes the fleet prompt cache-resident, the
        # second exercises the HIT path (suffix-bucket prefill + CoW) so its
        # executables compile outside the timed window
        arm_out = _run_serving_load(
            params, cfg, shared_prompts, args.clients, f"prefix-{arm}",
            num_pages=args.clients * 9 + 8,
            prime=[shared_prompts[0], shared_prompts[1]],
        )
        ttfts_arm = [o["ttft_s"] for o in arm_out["outs"] if o["ttft_s"] is not None]
        prefix_ttfts[arm] = _quantile(ttfts_arm, 0.5)
        prefix_stats[arm] = arm_out["stats"]
    os.environ.pop("MODAL_TPU_SERVING_PREFIX_CACHE", None)
    speedup = prefix_ttfts["off"] / max(1e-9, prefix_ttfts["on"])
    result["prefix_p50_ttft_off_s"] = round(prefix_ttfts["off"], 4)
    result["prefix_p50_ttft_on_s"] = round(prefix_ttfts["on"], 4)
    result["prefix_ttft_speedup"] = round(speedup, 2)
    result["prefix_cache_hits"] = prefix_stats["on"].get("prefix_cache_hits", 0)
    result["prefix_cache_cow_copies"] = prefix_stats["on"].get("kv_pages_cow_copies", 0)
    print(
        f"bench[serving]: shared-prefix p50 TTFT {prefix_ttfts['on']:.4f}s (cache on, "
        f"{result['prefix_cache_hits']} hits) vs {prefix_ttfts['off']:.4f}s (off) — "
        f"{speedup:.2f}x",
        file=sys.stderr,
    )

    # --- phase 5: speculative decoding with a genuinely smaller draft -----
    # Engine-level (the HTTP plane is benched above). PR 11's self-draft arm
    # pinned the MECHANISM (acceptance ~1.0, spec_speedup ~0.8x honest: a
    # same-cost draft cannot win on wall clock). This phase benches the
    # DEPLOYMENT shape — llm_service(draft_config=, draft_weights=) — with a
    # surrogate aligned pair built in-process: the draft is the 2-layer tiny
    # model; the target is a 12x-deeper tiny whose first layers ARE the
    # draft's and whose extra layers are residual-identity (attention `wo`
    # and MLP `w_down` zeroed, so under pre-norm residuals both sublayers
    # add exact zeros). Embed/final_norm/lm_head are shared, so the pair is
    # logits-aligned (acceptance near 1.0 — the residue is the fp32
    # verify-vs-decode executable near-tie caveat) while the target pays
    # ~12x the draft's per-step cost. Depth matters on CPU: a shallow
    # target's step is dispatch-overhead-bound, and the multi-token verify
    # only amortizes that overhead (the real-hardware memory-bandwidth win)
    # once per-layer work dominates the step. Acceptance: spec must BEAT
    # the non-spec target (spec_speedup > 1x, bench.py SPEC_SPEEDUP_FLOOR;
    # measured 1.25x at spec_k=4).
    import jax.numpy as jnp

    from modal_tpu.serving.engine import ServingEngine

    tgt_cfg = get_config("tiny", n_layers=12 * cfg.n_layers)
    tgt_seed = init_params(tgt_cfg, jax.random.PRNGKey(3))
    tgt_layers = {}
    for k, leaf in tgt_seed["layers"].items():
        tail = leaf[cfg.n_layers :]
        if k in ("wo", "w_down"):
            tail = jnp.zeros_like(tail)  # residual-identity: sublayer adds 0
        tgt_layers[k] = jnp.concatenate([params["layers"][k], tail], axis=0)
    tgt_params = {
        "embed": params["embed"],
        "layers": tgt_layers,
        "final_norm": params["final_norm"],
        "lm_head": params["lm_head"],
    }
    spec_prompts = prompts[:16]

    def _engine_tokens_per_s(draft) -> tuple:
        eng = ServingEngine(
            tgt_params, tgt_cfg, max_slots=8, num_pages=16 * 9 + 8, page_size=16,
            prefill_chunk=64, draft=draft, spec_k=4, prefix_cache=False,
        ).start()
        try:
            warm = eng.submit(spec_prompts[0], max_new_tokens=GEN_LEN)
            warm.result(timeout=300)
            t0 = time.perf_counter()
            reqs = [eng.submit(p, max_new_tokens=GEN_LEN) for p in spec_prompts]
            total = sum(len(r.result(timeout=300)) for r in reqs)
            wall = time.perf_counter() - t0
            return total / wall, eng.stats()
        finally:
            eng.stop()

    base_eng_tps, _st = _engine_tokens_per_s(None)
    spec_tps, spec_st = _engine_tokens_per_s((params, cfg))
    result["spec_tokens_per_s"] = round(spec_tps, 1)
    result["spec_baseline_tokens_per_s"] = round(base_eng_tps, 1)
    result["spec_speedup"] = round(spec_tps / max(1e-9, base_eng_tps), 2)
    result["spec_accept_ratio"] = spec_st.get("spec_accept_ratio")
    result["spec_rounds"] = spec_st.get("spec_rounds")
    result["spec_draft_layers"] = cfg.n_layers
    result["spec_target_layers"] = tgt_cfg.n_layers
    print(
        f"bench[serving]: speculative {spec_tps:.0f} vs {base_eng_tps:.0f} tokens/s "
        f"({result['spec_speedup']}x, {cfg.n_layers}L draft / {tgt_cfg.n_layers}L target), "
        f"accept ratio {result['spec_accept_ratio']}",
        file=sys.stderr,
    )

    # --- phase 6: cache-aware fleet routing + disaggregation (ISSUE 18) ---
    # Three engine replicas behind ServingRouter, hit with shared-prefix
    # traffic (6 families x 4 requests, 224-token family prefix + 4-token
    # suffixes). A/B on the ROUTER only (every engine keeps its prefix
    # cache): routed followers land on the family's cache holder; the
    # random arm (MODAL_TPU_SERVING_ROUTER=0 degradation) scatters them, so
    # most requests pay a cold full prefill. Acceptance: routed p50 TTFT
    # >= 2x better than random (bench.py FLEET_ROUTED_TTFT_FLOOR). A third
    # arm runs the disaggregated path (rep0 as the prefill tier, KV pages
    # shipped to the decode replicas via route(split_prefill=True)) and
    # reports shipment counts — its win is decode-replica HBM/cache
    # residency, not TTFT, so it carries no speed guard.
    from modal_tpu.serving.router import ServingRouter

    FLEET_GEN = 8
    fam_rng = np.random.default_rng(18)

    class _EngineTransport:
        """Direct-call replica transport (the router's contract is
        `callable(path, body) -> dict`; HTTP framing is benched in phases
        2-4). Shipments ride an in-memory store instead of the blob plane."""

        def __init__(self, name: str, engine, store: dict):
            self.name, self.engine, self.store = name, engine, store

        def __call__(self, path: str, body: dict) -> dict:
            rid = body.get("request_id", "")
            if path == "/v1/prefill":
                req = self.engine.prefill_export(body["prompt"], request_id=rid)
                req.result(timeout=300)
                ref = f"mem://{self.name}/{rid}"
                self.store[ref] = req.shipment
                req.shipment = None
                return {"kv_ref": ref, "request_id": rid}
            if path == "/v1/prefilled":
                ship = self.store.pop(body.get("kv_ref"), None)
                req = self.engine.submit_prefilled(
                    body["prompt"], ship, body.get("max_new_tokens", FLEET_GEN),
                    request_id=rid,
                )
            else:
                req = self.engine.submit(
                    body["prompt"], body.get("max_new_tokens", FLEET_GEN),
                    request_id=rid,
                )
            tokens = req.result(timeout=300)
            return {"tokens": tokens, "ttft_s": req.ttft_s}

    def _fleet_arm(enabled: bool, split: bool = False) -> tuple:
        os.environ["MODAL_TPU_SERVING_ROUTER"] = "1" if enabled else "0"
        engines = {
            f"rep{i}": ServingEngine(
                params, cfg, max_slots=4, num_pages=160, page_size=16,
                pages_per_slot=16, prefill_chunk=64,
                role="prefill" if (split and i == 0) else "both",
            ).start()
            for i in range(3)
        }
        store: dict = {}
        replicas = {n: _EngineTransport(n, e, store) for n, e in engines.items()}
        router = ServingRouter(
            replicas, page_size=16,
            prefill_replicas=("rep0",) if split else (),
        )
        families = []
        for _ in range(6):
            head = fam_rng.integers(0, cfg.vocab_size, size=224).tolist()
            families.append([
                head + fam_rng.integers(0, cfg.vocab_size, size=4).tolist()
                for _ in range(4)
            ])
        try:
            # warmup (untimed, excluded): every replica compiles the cold
            # full-prefill buckets, the suffix hit-path, and the decode
            # executable before the measured window
            warm_head = fam_rng.integers(0, cfg.vocab_size, size=224).tolist()
            for tr in replicas.values():
                tr("/v1/generate", {"prompt": warm_head + [1, 2, 3, 4]})
                tr("/v1/generate", {"prompt": warm_head + [5, 6, 7, 8]})
            ttfts = []
            for fam in families:
                for p in fam:
                    out = router.route(
                        {"prompt": p, "max_new_tokens": FLEET_GEN},
                        split_prefill=split,
                    )
                    if out.get("ttft_s") is not None:
                        ttfts.append(out["ttft_s"])
            eng_stats = {n: e.stats() for n, e in engines.items()}
        finally:
            os.environ.pop("MODAL_TPU_SERVING_ROUTER", None)
            for e in engines.values():
                e.stop()
        return ttfts, eng_stats, router.stats()

    routed_ttfts, routed_stats, routed_router = _fleet_arm(True)
    random_ttfts, random_stats, _rr = _fleet_arm(False)
    split_ttfts, split_stats, split_router = _fleet_arm(True, split=True)

    routed_p50 = _quantile(routed_ttfts, 0.5)
    random_p50 = _quantile(random_ttfts, 0.5)
    result["fleet_replicas"] = 3
    result["fleet_routed_p50_ttft_s"] = round(routed_p50, 4)
    result["fleet_random_p50_ttft_s"] = round(random_p50, 4)
    result["fleet_routed_vs_random_ttft"] = round(random_p50 / max(1e-9, routed_p50), 2)
    result["fleet_routed_prefix_hits"] = sum(
        s.get("prefix_cache_hits", 0) for s in routed_stats.values()
    )
    result["fleet_random_prefix_hits"] = sum(
        s.get("prefix_cache_hits", 0) for s in random_stats.values()
    )
    result["fleet_routed_reasons"] = routed_router["routed"]
    result["fleet_split_p50_ttft_s"] = round(_quantile(split_ttfts, 0.5), 4)
    result["fleet_remote_prefills"] = sum(
        s.get("remote_prefills", 0) for s in split_stats.values()
    )
    result["fleet_kv_pages_shipped"] = sum(
        s.get("kv_pages_shipped", 0) for s in split_stats.values()
    )
    result["fleet_prefill_fallbacks"] = split_router["prefill_fallbacks"]
    print(
        f"bench[serving]: fleet routed p50 TTFT {routed_p50:.4f}s vs random "
        f"{random_p50:.4f}s ({result['fleet_routed_vs_random_ttft']}x, reasons "
        f"{routed_router['routed']}); split arm shipped "
        f"{result['fleet_kv_pages_shipped']} KV pages over "
        f"{result['fleet_remote_prefills']} remote prefills "
        f"({result['fleet_prefill_fallbacks']} fallbacks)",
        file=sys.stderr,
    )

    print("SERVING_BENCH_RESULT " + json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
