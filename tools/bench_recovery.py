#!/usr/bin/env python
"""Durability microbench: journal append overhead on the RPC hot path +
journal replay speed (server/journal.py).

Runs the REAL control-plane handlers (ModalTPUServicer) in-process — no gRPC,
no workers — so the numbers isolate exactly what the write-ahead journal adds
to a mutating RPC:

- **append overhead**: N FunctionPutInputs handler calls with journaling OFF
  vs ON; the acceptance bar (ISSUE 4) is <= 5% added wall time per RPC.
- **replay**: build a journal of ~10k records (real enqueues + outputs
  through the handlers), then time ``recover_state`` into a fresh
  ServerState.

Emits ONE JSON line (``RECOVERY_BENCH_RESULT {...}``) so CI and bench.py can
fold it.

Usage:
    JAX_PLATFORMS=cpu python tools/bench_recovery.py [--rpcs 2000] [--replay-records 10000]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import shutil
import sys
import tempfile
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


class _Ctx:
    """Minimal grpc context stand-in for direct handler calls."""

    def invocation_metadata(self):
        return ()

    async def abort(self, code, details=""):
        raise RuntimeError(f"abort {code}: {details}")


async def _setup(state_dir: str, with_journal: bool):
    from modal_tpu.proto import api_pb2
    from modal_tpu.server.journal import IdempotencyCache, Journal
    from modal_tpu.server.services import ModalTPUServicer
    from modal_tpu.server.state import ServerState

    state = ServerState(state_dir)
    if with_journal:
        state.journal = Journal(state_dir)
        state.idempotency = IdempotencyCache(journal=state.journal)
    servicer = ModalTPUServicer(state)
    ctx = _Ctx()
    app = await servicer.AppCreate(api_pb2.AppCreateRequest(description="bench"), ctx)
    fn = await servicer.FunctionCreate(
        api_pb2.FunctionCreateRequest(
            app_id=app.app_id, function=api_pb2.Function(function_name="bench_fn"), tag="bench_fn"
        ),
        ctx,
    )
    call = await servicer.FunctionMap(
        api_pb2.FunctionMapRequest(
            function_id=fn.function_id, function_call_type=api_pb2.FUNCTION_CALL_TYPE_MAP
        ),
        ctx,
    )
    return servicer, ctx, fn.function_id, call.function_call_id


async def _bench_put_inputs(n_rpcs: int, with_journal: bool, payload: bytes) -> float:
    """Mean seconds per FunctionPutInputs handler call (1 input per call —
    the hot-path shape a pipelined map produces)."""
    from modal_tpu.proto import api_pb2

    d = tempfile.mkdtemp(prefix="bench-recovery-")
    try:
        servicer, ctx, function_id, call_id = await _setup(d, with_journal)
        # warmup (file handle open, code paths hot)
        for i in range(50):
            await servicer.FunctionPutInputs(
                api_pb2.FunctionPutInputsRequest(
                    function_id=function_id,
                    function_call_id=call_id,
                    inputs=[
                        api_pb2.FunctionPutInputsItem(
                            idx=i, input=api_pb2.FunctionInput(args=payload)
                        )
                    ],
                ),
                ctx,
            )
        t0 = time.perf_counter()
        for i in range(n_rpcs):
            await servicer.FunctionPutInputs(
                api_pb2.FunctionPutInputsRequest(
                    function_id=function_id,
                    function_call_id=call_id,
                    inputs=[
                        api_pb2.FunctionPutInputsItem(
                            idx=50 + i, input=api_pb2.FunctionInput(args=payload)
                        )
                    ],
                ),
                ctx,
            )
        took = time.perf_counter() - t0
        if with_journal and servicer.s.journal is not None:
            servicer.s.journal.close()
        return took / n_rpcs
    finally:
        shutil.rmtree(d, ignore_errors=True)


async def _bench_replay(n_records: int, payload: bytes) -> dict:
    """Build a journal of ~n_records real records, then time recovery."""
    from modal_tpu.proto import api_pb2
    from modal_tpu.server.journal import IdempotencyCache, Journal, recover_state
    from modal_tpu.server.state import ServerState

    d = tempfile.mkdtemp(prefix="bench-recovery-replay-")
    try:
        servicer, ctx, function_id, call_id = await _setup(d, with_journal=True)
        # each loop iteration appends 2 records (input + output); the setup
        # added a handful more — close enough to n_records for a rate number
        n_pairs = max(1, n_records // 2)
        input_ids = []
        for i in range(n_pairs):
            resp = await servicer.FunctionPutInputs(
                api_pb2.FunctionPutInputsRequest(
                    function_id=function_id,
                    function_call_id=call_id,
                    inputs=[
                        api_pb2.FunctionPutInputsItem(
                            idx=i, input=api_pb2.FunctionInput(args=payload)
                        )
                    ],
                ),
                ctx,
            )
            input_ids.append(resp.inputs[0].input_id)
        for i, input_id in enumerate(input_ids):
            await servicer.FunctionPutOutputs(
                api_pb2.FunctionPutOutputsRequest(
                    outputs=[
                        api_pb2.FunctionPutOutputsItem(
                            function_call_id=call_id,
                            input_id=input_id,
                            idx=i,
                            result=api_pb2.GenericResult(
                                status=api_pb2.GENERIC_STATUS_SUCCESS, data=payload
                            ),
                        )
                    ]
                ),
                ctx,
            )
        journal = servicer.s.journal
        total_records = journal.seq
        journal.close()
        fresh = ServerState(d)
        fresh.idempotency = IdempotencyCache(journal=None)
        replay_journal = Journal(d)
        t0 = time.perf_counter()
        report = recover_state(fresh, replay_journal)
        replay_s = time.perf_counter() - t0
        replay_journal.close()
        assert len(fresh.inputs) == n_pairs, f"replay lost inputs: {len(fresh.inputs)} != {n_pairs}"
        call = fresh.function_calls[call_id]
        assert call.num_done == n_pairs, f"replay lost outputs: {call.num_done} != {n_pairs}"
        return {
            "replay_records": total_records,
            "replay_s": round(replay_s, 4),
            "replay_records_per_s": round(total_records / replay_s) if replay_s > 0 else 0,
            "replay_applied": report["records_applied"],
        }
    finally:
        shutil.rmtree(d, ignore_errors=True)


async def _bench_grpc_put_inputs(n_rpcs: int, payload: bytes) -> tuple[float, float]:
    """(baseline_s, journaled_s) mean seconds per FunctionPutInputs over REAL
    gRPC (localhost) — the hot path the <=5% acceptance budget is measured
    against. One supervisor, one channel; the journal is toggled on/off in
    INTERLEAVED batches so process/loop aging drift (which dwarfs the
    journal's microseconds over a sequential A-then-B run) cancels out."""
    from modal_tpu._utils.grpc_utils import create_channel
    from modal_tpu.proto import api_pb2
    from modal_tpu.proto.rpc import ModalTPUStub
    from modal_tpu.server.supervisor import LocalSupervisor

    d = tempfile.mkdtemp(prefix="bench-recovery-grpc-")
    sup = LocalSupervisor(num_workers=0, state_dir=d)
    try:
        await sup.start()
        journal = sup.state.journal
        channel = create_channel(sup.server_url)
        stub = ModalTPUStub(channel)
        app = await stub.AppCreate(api_pb2.AppCreateRequest(description="bench"))
        fn = await stub.FunctionCreate(
            api_pb2.FunctionCreateRequest(
                app_id=app.app_id,
                function=api_pb2.Function(function_name="bench_fn"),
                tag="bench_fn",
            )
        )
        call = await stub.FunctionMap(
            api_pb2.FunctionMapRequest(
                function_id=fn.function_id, function_call_type=api_pb2.FUNCTION_CALL_TYPE_MAP
            )
        )
        next_idx = 0

        async def _put_batch(n: int) -> float:
            nonlocal next_idx
            t0 = time.perf_counter()
            for _ in range(n):
                await stub.FunctionPutInputs(
                    api_pb2.FunctionPutInputsRequest(
                        function_id=fn.function_id,
                        function_call_id=call.function_call_id,
                        inputs=[
                            api_pb2.FunctionPutInputsItem(
                                idx=next_idx, input=api_pb2.FunctionInput(args=payload)
                            )
                        ],
                    )
                )
                next_idx += 1
            return time.perf_counter() - t0

        await _put_batch(50)  # warmup
        batch = max(25, n_rpcs // 16)
        base_total = jrnl_total = 0.0
        base_n = jrnl_n = 0
        while base_n < n_rpcs or jrnl_n < n_rpcs:
            sup.state.journal = None
            base_total += await _put_batch(batch)
            base_n += batch
            sup.state.journal = journal
            jrnl_total += await _put_batch(batch)
            jrnl_n += batch
        await channel.close()
        return base_total / base_n, jrnl_total / jrnl_n
    finally:
        await sup.stop()
        shutil.rmtree(d, ignore_errors=True)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--rpcs", type=int, default=2000)
    parser.add_argument("--grpc-rpcs", type=int, default=800)
    parser.add_argument("--replay-records", type=int, default=10_000)
    parser.add_argument("--payload-bytes", type=int, default=1024)
    args = parser.parse_args()
    payload = os.urandom(args.payload_bytes)

    # handler-only (transport excluded): isolates the append's raw cost
    base_s = asyncio.run(_bench_put_inputs(args.rpcs, with_journal=False, payload=payload))
    jrnl_s = asyncio.run(_bench_put_inputs(args.rpcs, with_journal=True, payload=payload))
    # end-to-end gRPC: the hot path the acceptance budget applies to
    grpc_base_s, grpc_jrnl_s = asyncio.run(
        _bench_grpc_put_inputs(args.grpc_rpcs, payload=payload)
    )
    overhead_pct = (
        (grpc_jrnl_s - grpc_base_s) / grpc_base_s * 100.0 if grpc_base_s > 0 else 0.0
    )
    result = {
        "rpcs": args.rpcs,
        "grpc_rpcs": args.grpc_rpcs,
        "payload_bytes": args.payload_bytes,
        "handler_rpc_us": round(base_s * 1e6, 2),
        "handler_journaled_rpc_us": round(jrnl_s * 1e6, 2),
        "journal_append_us": round((jrnl_s - base_s) * 1e6, 2),
        "grpc_rpc_us": round(grpc_base_s * 1e6, 2),
        "grpc_journaled_rpc_us": round(grpc_jrnl_s * 1e6, 2),
        "journal_overhead_pct": round(overhead_pct, 2),
        "overhead_budget_pct": 5.0,
        "within_budget": overhead_pct <= 5.0,
    }
    result.update(asyncio.run(_bench_replay(args.replay_records, payload)))
    print("RECOVERY_BENCH_RESULT " + json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
